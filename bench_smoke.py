"""CPU smoke for the benchmark harnesses (`make bench-smoke`).

Runs tiny-shape configurations of bench.py (epoch worker) and
bench_bls.py on the CPU platform and asserts the JSON output contract
the external driver parses — so bench bit-rot (import errors, schema
drift, kernel regressions that crash at trace time) is caught without a
TPU.  The kzg worker is excluded: its mainnet 4096-wide blob shapes have
no tiny-shape knob and would dominate the lane's wall time.

The sub-benches run with CST_TELEMETRY=1 so the `"telemetry"` sub-object
(compile_s/run_s split, padding waste, MSM/h2c routing — see
`consensus_specs_tpu.telemetry`) is asserted present and schema-valid on
every metric line: the bench contract cannot silently drop it.  The
bench_bls run also sets CST_TRACE_FILE and checks the emitted Chrome
trace is loadable trace-event JSON, and probes the MSM break-even at one
tiny size (n=4) to keep the probe path exercised.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from pathlib import Path

from consensus_specs_tpu.telemetry import validate_bench_block
from consensus_specs_tpu.telemetry import history as benchwatch

HERE = Path(__file__).resolve().parent


def _run(cmd, env_extra, timeout):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.update(env_extra)
    print(f"--- {' '.join(cmd)} ---", file=sys.stderr, flush=True)
    proc = subprocess.run([sys.executable] + cmd, capture_output=True,
                          text=True, timeout=timeout, env=env,
                          cwd=str(HERE))
    if proc.stderr:
        sys.stderr.write(proc.stderr[-2000:])
        sys.stderr.flush()
    if proc.returncode != 0:
        raise SystemExit(f"{cmd}: rc={proc.returncode}")
    parsed = []
    for line in (proc.stdout or "").splitlines():
        if not line.strip():
            continue
        try:
            parsed.append(json.loads(line))
        except json.JSONDecodeError:
            raise SystemExit(f"{cmd}: non-JSON stdout line: {line!r}")
    if not parsed:
        raise SystemExit(f"{cmd}: produced no JSON line")
    return parsed


def _check_telemetry(record, where: str) -> dict:
    tel = record.get("telemetry")
    problems = validate_bench_block(tel)
    if problems:
        raise SystemExit(f"{where}: bad telemetry block {problems}: "
                         f"{json.dumps(tel)[:500]}")
    return tel


def main():
    out = _run(["bench.py", "--worker", "epoch"],
               {"CST_BENCH_N": "1024", "CST_NO_COMPILE_CACHE": "1",
                "CST_TELEMETRY": "1"},
               timeout=900)
    last = out[-1]
    assert isinstance(last.get("seconds"), (int, float)) \
        and last["seconds"] > 0, last
    tel = _check_telemetry(last, "epoch worker")
    assert tel["compile_s"] > 0, tel   # the fused step DID compile
    print("bench.py epoch worker JSON OK:",
          json.dumps({k: v for k, v in last.items() if k != "telemetry"}),
          f"(telemetry: compile {tel['compile_s']}s run {tel['run_s']}s)")

    trace_file = HERE / "out" / "smoke_trace.json"
    trace_file.parent.mkdir(exist_ok=True)
    if trace_file.exists():
        trace_file.unlink()
    # CST_BENCHWATCH_HISTORY makes every emitted metric line also land
    # in the longitudinal store; default to a scratch file so a local
    # smoke run does not pollute out/bench_history.jsonl, but let CI
    # point it AT the real store (its benchwatch job reports over it).
    # Only the scratch default is ever deleted — an externally named
    # store is longitudinal data this smoke must append to, not wipe.
    hist_env = os.environ.get("CST_BENCHWATCH_HISTORY")
    hist_file = Path(hist_env) if hist_env \
        else HERE / "out" / "smoke_history.jsonl"
    if not hist_env and hist_file.exists():
        hist_file.unlink()
    run_t0 = time.time()
    out = _run(["bench_bls.py"],
               {"CST_BLS_BENCH_N": "2", "CST_BLS_BENCH_COMMITTEE": "2",
                "CST_BLS_BENCH_SYNC": "4",
                "CST_TELEMETRY": "1", "CST_BLS_BENCH_MSM_SIZES": "4",
                "CST_TRACE_FILE": str(trace_file),
                "CST_BENCHWATCH_HISTORY": str(hist_file)},
               timeout=1800)
    metrics = [o for o in out if "metric" in o]
    assert len(metrics) == 3, out    # configs #2, #3 + the MSM probe
    for m in metrics:
        assert {"metric", "value", "unit", "vs_baseline"} <= set(m), m
        assert isinstance(m["value"], (int, float)), m
        _check_telemetry(m, m["metric"])
    probe = [m for m in metrics
             if m["metric"].startswith("g1_msm_breakeven_probe")]
    assert probe and probe[0].get("detail", {}).get("4"), probe
    print("bench_bls.py JSON OK:", json.dumps(
        [{k: v for k, v in m.items() if k != "telemetry"}
         for m in metrics]))

    # the benchwatch history-record contract: every metric line this run
    # emitted must have landed in the store as one schema-valid record,
    # platform-stamped "cpu" (the smoke pin).  Assertions apply to THIS
    # run's records (ts >= run start, with clock slack) — a pre-existing
    # external store may hold anything
    hist_records, skipped, hist_warns = benchwatch.load_history(hist_file)
    if not hist_env:     # we created the scratch file fresh
        assert not skipped and not hist_warns, (skipped, hist_warns)
    fresh = [r for r in hist_records
             if isinstance(r.get("ts"), (int, float))
             and r["ts"] >= run_t0 - 5]
    stored = {r["metric"] for r in fresh}
    assert {m["metric"] for m in metrics} <= stored, (stored, metrics)
    for rec in fresh:
        problems = benchwatch.validate_record(rec)
        assert not problems, (problems, rec)
        assert rec["source"] == "bench_emit", rec
        assert rec["platform"] == "cpu", rec
    probe_rec = [r for r in fresh
                 if r["metric"].startswith("g1_msm_breakeven_probe")]
    assert probe_rec and probe_rec[0].get("detail", {}).get("4"), probe_rec
    print(f"benchwatch history OK: {len(fresh)} records this run -> "
          f"{hist_file}")

    # CST_TRACE_FILE must have produced loadable Chrome trace-event JSON
    trace = json.loads(trace_file.read_text())
    events = trace["traceEvents"]
    spans = [e for e in events if e.get("ph") == "X"]
    assert spans, "trace file has no complete ('X') events"
    for e in spans:
        assert {"name", "ph", "ts", "dur", "pid", "tid"} <= set(e), e
    names = {e["name"] for e in spans}
    assert "bls.batch_verify" in names, sorted(names)
    print(f"chrome trace OK: {len(spans)} spans -> {trace_file}")

    # telemetry-OFF contract: the default path (what a non-telemetry
    # TPU round runs) must emit the plain 2-metric lines — no
    # "telemetry" key, no probe.  Same shapes as the run above, so the
    # persistent compile cache makes this re-run cheap.
    out = _run(["bench_bls.py"],
               {"CST_BLS_BENCH_N": "2", "CST_BLS_BENCH_COMMITTEE": "2",
                "CST_BLS_BENCH_SYNC": "4",
                "CST_TELEMETRY": "", "CST_TRACE_FILE": ""},
               timeout=1800)
    metrics = [o for o in out if "metric" in o]
    assert len(metrics) == 2, out
    for m in metrics:
        assert {"metric", "value", "unit", "vs_baseline"} <= set(m), m
        assert "telemetry" not in m, m
    print("bench_bls.py telemetry-off JSON OK:", json.dumps(metrics))
    print("bench smoke: PASS")


if __name__ == "__main__":
    main()
