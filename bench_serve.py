"""Sustained-load serving benchmark — the ROADMAP's attestation-
verification service under continuous traffic.

Every other bench measures one cold batch; this one drives the
`consensus_specs_tpu.serve` executor (deferred-result futures, AOT-
warmed `_bucket` executables, double-buffered batch pipeline) with the
mainnet per-slot arrival mix until throughput reaches steady state
(last 3 windows within ±20%), then prints ONE JSON metric line:

  {"metric": "serve_sustained_load", "value": <verifies/s>,
   "unit": "verifies/s", "vs_baseline": <x vs the oracle's
   FastAggregateVerify rate>, "serve": {...}}

The `"serve"` sub-object is `serve.loadgen.run_load`'s block (schema
pinned by `telemetry.export.validate_serve_block`): steady-state
verifies/sec, p50/p99 batch latency, window rates, queue-depth
histogram, pipeline stats.  `vs_baseline` divides the measured rate by
the persisted pure-Python oracle's single-verify rate
(bench_bls_baseline.json) — the per-core signatures/sec framing of
PAPERS.md's EdDSA-vs-BLS committee-consensus paper.

Exit-code contract: nonzero when loadgen never reached steady state
within its ≤3x window extension — the metric line then carries an
explicit `"error"` naming the non-convergence (and `serve.steady` is
false), instead of reporting the last unconverged window as if it were
a steady-state rate.

Resilience: `CST_FAULTS` installs a fault plan before the load runs
(the seams stay zero-overhead without it), and `CST_SERVE_CHAOS=1`
switches to the chaos harness (`resilience.chaos.run_chaos_load`):
baseline → faults live (breaker/oracle-fallback degraded mode) →
recovery-to-steady, with the `"resilience"` sub-object (schema
`validate_resilience_block`) embedded in the metric line and mined into
`resilience::*` benchwatch records for the `chaos-recovery` /
`chaos-correctness` threshold rows.  A chaos round additionally exits
nonzero on any wrong result or when the service never recovers.

Request tracing: `CST_TRACE_REQUESTS=1` mints a per-request
`RequestContext` at every submit (chaos rounds arm it automatically) —
the serve block's p50/p99 switch to per-request submit→complete
semantics (`latency_source: "reqtrace"`), a `latency_attribution`
sub-object decomposes the per-kind tail into
queue_wait/batch_form/device_wall/settle/detour, `latency::*` history
records feed the report's "Tail latency" section, and the worst-N
exemplar traces are written to `out/serve_exemplars.json` (the CI
artifact).  `CST_SERVE_STATUS_EVERY=<s>` additionally dumps the
executor's live `status()` JSON on stderr while the round runs.

Monitoring: `CST_METRICS_PORT=<port>` serves live Prometheus text
exposition while the round runs (the loadgen self-scrapes it mid-round
into `out/metrics_scrape.txt`), and `CST_SLO_RULES=...` arms the live
SLO watchdog — the serve block gains the `"slo"` evidence sub-object
(schema `validate_slo_block`, mined into `slo::*` records for the
`slo-clean-round` threshold row) and the breach evidence is written to
`out/slo_breaches.json` (`out/chaos_slo_breaches.json` on chaos
rounds, where the deterministic breach→clear arc is asserted and gated
by `chaos-slo-arc`).  See README "Monitoring".

Knobs are the CST_SERVE_* family (README "Serving"); the CPU smoke runs
closed-loop (`CST_SERVE_RATE=0`) so the measured rate is the host's
capacity instead of an idle fixed-rate clock.  With CST_TELEMETRY=1 the
line also carries the standard `"telemetry"` block, and
CST_BENCHWATCH_HISTORY lands `serve::*` (and `resilience::*`) history
records for the benchwatch threshold rows.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

import jax

jax.config.update("jax_enable_x64", True)
# the image's sitecustomize pins the platform to the pooled TPU through
# live config; let an explicit JAX_PLATFORMS env override it (CPU smoke)
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from consensus_specs_tpu import telemetry  # noqa: E402
from consensus_specs_tpu.resilience import faults  # noqa: E402
from consensus_specs_tpu.telemetry import history as benchwatch  # noqa: E402
from consensus_specs_tpu.utils.jaxtools import enable_compile_cache  # noqa: E402

enable_compile_cache()

BLS_BASELINE_FILE = (Path(__file__).resolve().parent
                     / "bench_bls_baseline.json")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _oracle_verifies_per_s() -> float | None:
    """The pure-Python oracle's FastAggregateVerify rate (verifies/s)
    from the persisted baseline — the denominator of `vs_baseline`."""
    try:
        data = json.loads(BLS_BASELINE_FILE.read_text())
        per_verify = float(data["oracle_seconds_per_fast_aggregate_verify"])
        return 1.0 / per_verify if per_verify > 0 else None
    except (OSError, KeyError, ValueError, TypeError):
        return None


def _emit(record: dict) -> None:
    """One metric line on stdout, `"telemetry"` embedded on telemetry
    rounds, history records appended when CST_BENCHWATCH_HISTORY names
    a path — the same contract as bench.py / bench_bls.py."""
    record = telemetry.embed_bench_block(record)
    benchwatch.append_emission(record, ts=time.time())
    print(json.dumps(record), flush=True)


def main() -> int:
    from consensus_specs_tpu.serve.loadgen import config_from_env, run_load
    from consensus_specs_tpu.telemetry import (
        validate_resilience_block,
        validate_serve_block,
    )

    chaos = os.environ.get("CST_SERVE_CHAOS", "0") not in ("", "0")
    cfg = config_from_env()
    log(f"serve bench: {cfg} on "
        f"{jax.devices()[0].platform}:{len(jax.devices())}"
        + (" [CHAOS]" if chaos else ""))
    if not chaos and faults.plan_from_env_source():
        # run_load installs the plan itself, after kernel warmup (the
        # chaos harness instead owns install/clear phase by phase); the
        # executor arms retry/breaker/fallback automatically
        log(f"serve bench: fault plan ARMED: "
            f"{faults.load_plan(faults.plan_from_env_source()).describe()}")
    block = run_load(cfg)
    problems = validate_serve_block(block)
    res = block.get("resilience")
    if chaos:
        problems += validate_resilience_block(res)
    if problems:
        log(f"serve bench: INVALID serve block: {problems}")
        return 1
    oracle_rate = _oracle_verifies_per_s()
    vs_baseline = (round(block["verifies_per_s"] / oracle_rate, 2)
                   if oracle_rate else None)
    record = {
        "metric": "serve_sustained_load",
        "value": block["verifies_per_s"],
        "unit": "verifies/s",
        "vs_baseline": vs_baseline,
        "serve": {k: v for k, v in block.items() if k != "resilience"},
    }
    if res is not None:
        record["resilience"] = res
    la = block.get("latency_attribution")
    if la is not None:
        # worst-N exemplar traces as a standalone artifact (CI uploads
        # both): enough to reconstruct WHERE each tail request's wall
        # went without re-running the round.  Chaos rounds write their
        # own file so the CI job's later chaos-smoke step cannot
        # clobber the serve-smoke step's exemplars
        exemplars = Path(__file__).resolve().parent / "out" / \
            ("chaos_exemplars.json" if chaos else "serve_exemplars.json")
        exemplars.parent.mkdir(exist_ok=True)
        exemplars.write_text(json.dumps(
            {"metric": "serve_sustained_load",
             "latency_source": block.get("latency_source"),
             "p99_queue_frac": la.get("p99_queue_frac"),
             "kinds": {k: v.get("p99_components_ms")
                       for k, v in la.get("kinds", {}).items()},
             "worst": la.get("worst", [])}, indent=1) + "\n")
        log(f"serve bench: tail attribution — p99 queue frac "
            f"{la.get('p99_queue_frac')}, worst exemplars -> "
            f"{exemplars}")
    slo = block.get("slo")
    if slo is not None:
        # the watchdog's breach evidence as a standalone artifact (CI
        # uploads it next to the exemplars): the per-rule summary plus
        # the bounded breach→clear event log with exemplar payloads
        slo_out = Path(__file__).resolve().parent / "out" / \
            ("chaos_slo_breaches.json" if chaos else "slo_breaches.json")
        slo_out.parent.mkdir(exist_ok=True)
        slo_out.write_text(json.dumps(
            {"metric": "serve_sustained_load", "slo": slo}, indent=1)
            + "\n")
        log(f"serve bench: SLO watchdog — {slo['breaches']} breach(es) "
            f"over {slo['ticks']} tick(s), evidence -> {slo_out}")
    rc = 0
    if not block["steady"]:
        # the exit-code contract: an unconverged run must not pass for
        # a steady-state measurement — say so IN the metric line too
        record["error"] = ("loadgen never reached steady state within "
                           "the 3x window extension")
        rc = 1
    if chaos and (res["wrong_results"] > 0 or not res["recovered"]):
        record["error"] = (f"chaos round failed: "
                           f"{res['wrong_results']} wrong result(s), "
                           f"recovered={res['recovered']}")
        rc = 1
    _emit(record)
    log(f"serve bench: {block['verifies_per_s']} verifies/s "
        f"(steady={block['steady']}, {block['mode']} loop), "
        f"p50 {block['p50_ms']} ms / p99 {block['p99_ms']} ms, "
        f"{block['settled']} settled in {block['duration_s']}s"
        + (f", {vs_baseline}x oracle" if vs_baseline else ""))
    if chaos:
        log(f"serve bench: chaos — {res['faults_injected']} fault(s), "
            f"{res['wrong_results']} wrong / {res['checked_results']} "
            f"checked, {res['fallbacks']} oracle-fallback, "
            f"{res['retries']} retried, breaker trips "
            f"{res['breaker']['trips']}, recovery "
            f"{res['recovery_latency_s']}s, degraded "
            f"{res['degraded_verifies_per_s']} verifies/s "
            f"(baseline {res['baseline_verifies_per_s']}), merkle heal "
            f"{res['heal']['recovery_s']}s")
    if rc:
        log(f"serve bench: FAILED — {record['error']}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
