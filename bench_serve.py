"""Sustained-load serving benchmark — the ROADMAP's attestation-
verification service under continuous traffic.

Every other bench measures one cold batch; this one drives the
`consensus_specs_tpu.serve` executor (deferred-result futures, AOT-
warmed `_bucket` executables, double-buffered batch pipeline) with the
mainnet per-slot arrival mix until throughput reaches steady state
(last 3 windows within ±20%), then prints ONE JSON metric line:

  {"metric": "serve_sustained_load", "value": <verifies/s>,
   "unit": "verifies/s", "vs_baseline": <x vs the oracle's
   FastAggregateVerify rate>, "serve": {...}}

The `"serve"` sub-object is `serve.loadgen.run_load`'s block (schema
pinned by `telemetry.export.validate_serve_block`): steady-state
verifies/sec, p50/p99 batch latency, window rates, queue-depth
histogram, pipeline stats.  `vs_baseline` divides the measured rate by
the persisted pure-Python oracle's single-verify rate
(bench_bls_baseline.json) — the per-core signatures/sec framing of
PAPERS.md's EdDSA-vs-BLS committee-consensus paper.

Knobs are the CST_SERVE_* family (README "Serving"); the CPU smoke runs
closed-loop (`CST_SERVE_RATE=0`) so the measured rate is the host's
capacity instead of an idle fixed-rate clock.  With CST_TELEMETRY=1 the
line also carries the standard `"telemetry"` block, and
CST_BENCHWATCH_HISTORY lands `serve::*` history records for the
benchwatch threshold rows (steady-state throughput, p99 latency).
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

import jax

jax.config.update("jax_enable_x64", True)
# the image's sitecustomize pins the platform to the pooled TPU through
# live config; let an explicit JAX_PLATFORMS env override it (CPU smoke)
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from consensus_specs_tpu import telemetry  # noqa: E402
from consensus_specs_tpu.telemetry import history as benchwatch  # noqa: E402
from consensus_specs_tpu.utils.jaxtools import enable_compile_cache  # noqa: E402

enable_compile_cache()

BLS_BASELINE_FILE = (Path(__file__).resolve().parent
                     / "bench_bls_baseline.json")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _oracle_verifies_per_s() -> float | None:
    """The pure-Python oracle's FastAggregateVerify rate (verifies/s)
    from the persisted baseline — the denominator of `vs_baseline`."""
    try:
        data = json.loads(BLS_BASELINE_FILE.read_text())
        per_verify = float(data["oracle_seconds_per_fast_aggregate_verify"])
        return 1.0 / per_verify if per_verify > 0 else None
    except (OSError, KeyError, ValueError, TypeError):
        return None


def _emit(record: dict) -> None:
    """One metric line on stdout, `"telemetry"` embedded on telemetry
    rounds, history records appended when CST_BENCHWATCH_HISTORY names
    a path — the same contract as bench.py / bench_bls.py."""
    record = telemetry.embed_bench_block(record)
    benchwatch.append_emission(record, ts=time.time())
    print(json.dumps(record), flush=True)


def main() -> int:
    from consensus_specs_tpu.serve.loadgen import config_from_env, run_load
    from consensus_specs_tpu.telemetry import validate_serve_block

    cfg = config_from_env()
    log(f"serve bench: {cfg} on "
        f"{jax.devices()[0].platform}:{len(jax.devices())}")
    block = run_load(cfg)
    problems = validate_serve_block(block)
    if problems:
        log(f"serve bench: INVALID serve block: {problems}")
        return 1
    oracle_rate = _oracle_verifies_per_s()
    vs_baseline = (round(block["verifies_per_s"] / oracle_rate, 2)
                   if oracle_rate else None)
    _emit({
        "metric": "serve_sustained_load",
        "value": block["verifies_per_s"],
        "unit": "verifies/s",
        "vs_baseline": vs_baseline,
        "serve": block,
    })
    log(f"serve bench: {block['verifies_per_s']} verifies/s "
        f"(steady={block['steady']}, {block['mode']} loop), "
        f"p50 {block['p50_ms']} ms / p99 {block['p99_ms']} ms, "
        f"{block['settled']} settled in {block['duration_s']}s"
        + (f", {vs_baseline}x oracle" if vs_baseline else ""))
    if not block["steady"]:
        log("serve bench: WARNING — did not reach steady state "
            "(windows: " + ", ".join(str(w) for w in block["windows"])
            + ")")
    return 0


if __name__ == "__main__":
    sys.exit(main())
