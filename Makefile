# consensus_specs_tpu — developer entry points (the reference's
# Makefile:73-271 equivalents, adapted: no pip installs are available in
# this environment, so `lint` is a compile + full-spec-build check instead
# of ruff/mypy).

PYTHON ?= python
CPU_ENV = JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8
VECTOR_OUT ?= out/vectors

.PHONY: test test-fast test-all test-bls lint vectors kzg_setups bench \
	bench-smoke bench-report serve serve-smoke chaos-smoke \
	chaos-mesh-smoke shard-smoke das-smoke fc-smoke multichip \
	incident help

help:
	@echo "targets: test (fast suite) | test-all (incl. slow crypto) |"
	@echo "  test-bls (operation suites with real signatures, jax backend) |"
	@echo "  lint (compile + spec static checks + device-path analyzer) |"
	@echo "  vectors [VECTOR_OUT=dir] |"
	@echo "  kzg_setups | bench (real TPU) | bench-smoke (tiny CPU shapes,"
	@echo "  asserts the bench JSON contract) | bench-report (benchwatch"
	@echo "  trend/threshold dashboard over the checked-in rounds +"
	@echo "  out/bench_history.jsonl; exits nonzero on regression) |"
	@echo "  serve (sustained-load verification service, real TPU;"
	@echo "  CST_TRACE_REQUESTS=1 adds per-request tail-latency"
	@echo "  attribution, CST_SERVE_STATUS_EVERY=N live status dumps) |"
	@echo "  serve-smoke (short closed-loop CPU serve round with request"
	@echo "  tracing, emits the serve bench JSON + benchwatch history +"
	@echo "  worst-N exemplar traces) | chaos-smoke (serve"
	@echo "  round under a canned fault plan: breaker/oracle-fallback"
	@echo "  degraded mode, checkpoint kill/restore, flagship breaker,"
	@echo "  recovery-to-steady, resilience records) | chaos-mesh-smoke"
	@echo "  (same + shard-loss recovery on a simulated 8-device mesh) |"
	@echo "  shard-smoke (tiny mesh-sharded flagship scaling rung on the"
	@echo "  simulated 8-device mesh, asserts the scaling::* record"
	@echo "  round-trip + report) | das-smoke (PeerDAS cell-proof sweep"
	@echo "  at the 128x8 sampling matrix on CPU: das block schema,"
	@echo "  >=2x speedup vs the pure-Python oracle, FK20 producer +"
	@echo "  recover round, das::* round-trip + report) | fc-smoke"
	@echo "  (device LMD-GHOST sweep on a tiny CPU"
	@echo "  tree: forkchoice block schema, >=2x speedup vs the phase0"
	@echo "  spec oracle, bit-exact head parity, forkchoice::*"
	@echo "  round-trip + report) | incident (on-demand flight-recorder"
	@echo "  bundle -> out/incidents/) | multichip (8-dev CPU dryrun)"

test:
	$(PYTHON) -m pytest tests/ -q -m "not slow"

# the reference's default is BLS ON (`Makefile:105` bls=fastest); this
# lane runs the signature-sensitive suites with real crypto on the jax
# backend so invalid-signature rejection paths execute every round
test-bls:
	$(CPU_ENV) $(PYTHON) -m pytest \
		tests/phase0/block_processing tests/electra/block_processing \
		tests/eip7732 tests/test_executor.py \
		-q --enable-bls --bls-type=jax

test-all:
	$(PYTHON) -m pytest tests/ -q

lint:
	$(PYTHON) -m compileall -q consensus_specs_tpu tests bench.py __graft_entry__.py
	$(CPU_ENV) $(PYTHON) -m consensus_specs_tpu.lint
	$(PYTHON) -m consensus_specs_tpu.analysis

vectors:
	$(CPU_ENV) $(PYTHON) -m consensus_specs_tpu.gen --output $(VECTOR_OUT) \
		--runners sanity operations epoch_processing finality genesis \
		rewards random transition forks shuffling ssz_generic networking

kzg_setups:
	$(CPU_ENV) $(PYTHON) -m consensus_specs_tpu.utils.kzg_setup \
		--secret 1337 --g1-length 4096 --g2-length 65 \
		--output-dir out/trusted_setups

bench:
	$(PYTHON) bench.py

# no TPU required: tiny-shape epoch + BLS bench runs on CPU, asserting
# the one-JSON-line-per-metric contract the external driver parses —
# including the CST_TELEMETRY "telemetry" sub-object (compile/run split,
# padding waste, MSM/h2c routing) and the CST_TRACE_FILE Chrome trace
bench-smoke:
	$(CPU_ENV) $(PYTHON) bench_smoke.py

# benchwatch: ingest BENCH_r*/MULTICHIP_r* rounds, baselines, and any
# telemetry snapshot into out/bench_history.jsonl, render the markdown
# trend + ROADMAP-threshold dashboard (out/bench_report.md), and exit
# nonzero on a round-over-round regression (CI gates on this; stdlib
# only, no jax)
bench-report:
	$(PYTHON) -m consensus_specs_tpu.telemetry.report --out out/bench_report.md

# the sustained-load attestation-verification service benchmark
# (consensus_specs_tpu/serve): mainnet-rate arrival mix through the
# deferred-futures executor, reports steady-state verifies/sec +
# p50/p99 batch latency (CST_SERVE_* knobs, README "Serving")
serve:
	$(PYTHON) bench_serve.py

# no TPU required: short closed-loop serve round on tiny CPU shapes —
# the measured rate is the host's capacity, the JSON contract, the
# serve::* history records, and (CST_TRACE_REQUESTS=1) the per-request
# latency_attribution block + worst-N exemplar artifact are what CI
# checks.  CST_METRICS_PORT + CST_SLO_RULES arm the live exposition
# endpoint (self-scraped mid-round into out/metrics_scrape.txt) and
# the SLO watchdog (evidence -> out/slo_breaches.json, slo::* records
# for the slo-clean-round report row); the generous thresholds mean a
# healthy round ends clean — breaches here are real findings
serve-smoke:
	@$(CPU_ENV) CST_SERVE_DURATION_S=12 CST_SERVE_RATE=0 CST_SERVE_POOL=4 \
		CST_SERVE_COMMITTEE=4 CST_SERVE_MAX_BATCH=8 CST_SERVE_WINDOWS=3 \
		CST_TRACE_REQUESTS=1 CST_METRICS_PORT=9464 CST_OCCUPANCY=1 \
		CST_SLO_RULES='serve.p99_ms<100000:name=p99-sane; serve.queue_depth<100000:name=queue-sane' \
		$(PYTHON) bench_serve.py

# no TPU required: the chaos round — bench_serve under CST_SERVE_CHAOS=1
# with a canned fault plan injecting dispatch failures into the RLC
# kernel.  Asserts zero wrong results, breaker trip -> oracle-fallback
# degraded mode -> re-close, finite recovery latency, the "resilience"
# block schema, the resilience::* history round-trip, and the report's
# Resilience section + chaos-recovery threshold row (CI gates on this)
chaos-smoke:
	$(CPU_ENV) $(PYTHON) bench_smoke.py --chaos

# on-demand incident dump from whatever process state is reachable:
# writes a self-contained bundle (manifest + event ring + fault plan +
# exemplars + metrics + state) under out/incidents/ and validates its
# own manifest.  The automatic triggers are CST_FLIGHTREC_ON_BREACH=1
# (one bundle per breached SLO rule) and CST_FLIGHTREC_POISON_N (poison
# storms) — see README "Flight recorder"
incident:
	$(CPU_ENV) $(PYTHON) -m consensus_specs_tpu.telemetry.flightrec

# no TPU required: the simulated-mesh chaos round — CPU_ENV forces 8
# host devices, CST_CHAOS_MESH arms the shard-loss segment: one
# injected device_loss into batch_verify_sharded, the lost shard's
# statements re-bucket over the surviving 7 devices (zero wrong or
# dropped), an invalid statement still rejects while degraded, and the
# half-open probe re-admits the full mesh.  Asserts the mesh::* record
# round-trip + the mesh-recovery / mesh-lost-statements threshold rows
chaos-mesh-smoke:
	$(CPU_ENV) $(PYTHON) bench_smoke.py --chaos-mesh

# no TPU required: a tiny mesh-sharded flagship scaling rung on the
# simulated 8-device mesh (the partition-registry epoch pipeline),
# asserting the "scaling" block schema, the scaling::* history-record
# round-trip, and the report's Scaling section.  The TPU-gated
# scaling-efficiency / flagship-8m threshold rows read 'no data' here —
# the smoke pins the plumbing, the chip pins the number
shard-smoke:
	$(CPU_ENV) $(PYTHON) bench_smoke.py --shard

# no TPU required: the PeerDAS cell-proof sweep at the full 128x8
# sampling matrix (1024 cells in ONE RLC pairing equation — the
# largest device batch in the repo).  Asserts the "das" block schema,
# the >= 2x das-speedup acceptance vs the pure-Python fulu oracle
# (oracle measured on a cell subset and scaled — its per-cell Lagrange
# interpolation makes a full-matrix oracle run hours), the
# mixed-invalid isolation arc, the coset-barycentric cross-check, and
# the das::* history/report/threshold wiring (CI gates on this).
# The same run covers the FK20 producer + damaged-matrix recover
# round: byte-parity vs the closed form, >= 4x das-producer-speedup
# vs the D_u MSM route, >= 2x das-recover-speedup vs the pure-Python
# recover oracle (both CPU-evaluable)
das-smoke:
	$(CPU_ENV) $(PYTHON) bench_smoke.py --das

# no TPU required: the device LMD-GHOST sweep on a tiny CPU tree (64
# blocks x 1024 validators).  Asserts the "forkchoice" block schema,
# the >= 2x fc-speedup acceptance vs the phase0 spec oracle's
# get_head (the oracle walks every active validator per child in pure
# Python; measured on a validator subset and scaled linearly),
# bit-exact device-vs-oracle head parity, and the forkchoice::*
# history/report/threshold wiring (CI gates on this)
fc-smoke:
	$(CPU_ENV) $(PYTHON) bench_smoke.py --forkchoice

multichip:
	$(CPU_ENV) $(PYTHON) -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('ok')"
